"""Multi-tenant serving: many GAME models behind one compiled ladder.

Photon deployments are inherently multi-model — per-market, per-surface,
per-experiment GLMix variants served side by side. The scorer refactor
(serving/scorer.py) made the compiled (mode × bucket) programs
shape-keyed, so hosting N same-shape tenants costs ONE warmup ladder:
tenant #2..N warm at near-zero compile cost (the bench asserts ≤1.1×
the single-tenant program count for 8 tenants).

``MultiTenantEngine`` hosts one ``ServingEngine`` per tenant under a
single shared bucket-ladder configuration and routes by the request's
``tenant`` field (the JSONL protocol's ``"tenant"`` key). Per-tenant
engines are the isolation boundary, deliberately: each tenant keeps its
OWN admission queue, SLO depths, circuit breaker, shadow capture, and
swap/probation state, so one tenant's breaker trip, SLO shed, or noisy
hot loop can never degrade a neighbor's scores — per-tenant scores stay
bitwise-equal to a dedicated single-tenant engine (the isolation test's
contract). What is shared is exactly what is safe to share: the
compiled programs (shape-keyed, parameters are arguments) and the
ladder geometry. Mixed-tenant micro-batches are impossible by
construction — a batch's gather tables belong to one model — so
"one MicroBatcher ladder" means one ladder shape with per-tenant
queues, not one queue.

On top of routing:

* **Admission budgets** — an optional per-tenant cap on queued depth
  (``admission_budget``), checked before the tenant's own engine sees
  the request: a flooding tenant gets typed TENANT_BUDGET_EXCEEDED
  refusals once ITS queue is full, bounding the device work it can put
  in front of its neighbors' batches (the ``tenant_hot_loop`` chaos
  test measures exactly this). The engine's own SLO shed/reject depths
  still apply underneath.
* **Canary / A-B splitting** — ``start_canary`` runs serving/swap.py's
  FULL gate ladder (finite, staging, shadow, int8, zero-compile) via
  ``swap_staged(..., publish=False)`` and, on pass, hosts the candidate
  in a canary arm that receives a deterministic hash-based fraction of
  the tenant's traffic: ``crc32("tenant:uid") % 10000 < fraction·10000``
  — stable per uid across processes, no RNG. Responses carry typed
  per-arm attribution (``arm="live"|"canary"``); ``promote_canary``
  publishes the canary model into the live engine (normal swap
  semantics: prior retained, probation armed), ``abort_canary`` drops it.
* **Per-tenant observability** — engines get ``tenant=...`` obs labels
  (warmup gauges become ``serving.warmup_seconds{tenant=...}`` etc. and
  survive ``obs.merge_snapshots`` as distinct keys), and routing emits
  ``serving.tenant_requests/responses/refused`` counters.
"""

from __future__ import annotations

import threading
import zlib
from typing import Dict, List, Optional, Sequence

from photon_tpu.obs.metrics import registry as _metrics
from photon_tpu.resilience import chaos as _chaos
from photon_tpu.serving.engine import ServingEngine
from photon_tpu.serving.model_state import DeviceResidentModel
from photon_tpu.serving.types import (Fallback, FallbackReason,
                                      ScoreRequest, ScoreResponse,
                                      ServingConfig)
from photon_tpu.utils import compile_cache

#: flood requests injected by the tenant_hot_loop chaos hook carry this
#: uid prefix; their responses are dropped (counted), never emitted
_FLOOD_PREFIX = "__chaos_flood__"

#: the two traffic arms a tenant can serve from
ARMS = ("live", "canary")


class TenantState:
    """One hosted tenant: its live engine, optional canary arm, and
    routing counters. Internal to MultiTenantEngine."""

    def __init__(self, name: str, engine: ServingEngine,
                 admission_budget: Optional[int]):
        self.name = name
        self.engine = engine
        self.admission_budget = admission_budget
        self.canary_engine: Optional[ServingEngine] = None
        self.canary_label: Optional[str] = None
        self.canary_fraction: float = 0.0
        self.split_counts = {"live": 0, "canary": 0}

    def depth(self) -> int:
        d = self.engine.batcher.depth()
        if self.canary_engine is not None:
            d += self.canary_engine.batcher.depth()
        return d


class MultiTenantEngine:
    """N tenants, one compiled bucket ladder, per-tenant isolation."""

    def __init__(self, config: Optional[ServingConfig] = None,
                 clock=None, default_tenant: Optional[str] = None):
        #: the shared ladder geometry; per-tenant configs may override
        #: SLO/breaker/swap knobs but MUST keep the same bucket ladder
        #: (max_batch / min_bucket / feature_pad) — those are compiled-
        #: program shapes, and one ladder is the point
        self.config = config or ServingConfig()
        self._clock = clock
        self.tenants: Dict[str, TenantState] = {}
        self.default_tenant = default_tenant
        self._lock = threading.Lock()

    # -- tenant lifecycle ----------------------------------------------------

    def _check_ladder(self, cfg: ServingConfig) -> None:
        host = self.config
        if (cfg.max_batch, cfg.min_bucket, cfg.feature_pad) != \
                (host.max_batch, host.min_bucket, host.feature_pad):
            raise ValueError(
                "tenant config must share the host bucket ladder "
                f"(max_batch={host.max_batch}, min_bucket={host.min_bucket}, "
                f"feature_pad={host.feature_pad}) — those are compiled-"
                "program shapes")

    def add_tenant(self, name: str, model: DeviceResidentModel,
                   config: Optional[ServingConfig] = None,
                   admission_budget: Optional[int] = None,
                   warm: bool = True) -> dict:
        """Host ``model`` as tenant ``name`` (its engine is built with
        ``tenant=name`` obs labels). The first tenant becomes the default
        route for tenant-less requests unless a default was configured.
        With ``warm=True`` the tenant's ladder is warmed immediately —
        a jitcache hit per program when a same-shape tenant (or a loaded
        program bundle) already populated the shape's programs."""
        cfg = config or self.config
        self._check_ladder(cfg)
        with self._lock:
            if name in self.tenants:
                raise ValueError(f"tenant {name!r} already hosted")
            engine = ServingEngine(model, config=cfg, clock=self._clock,
                                   obs_labels={"tenant": name})
            self.tenants[name] = TenantState(name, engine, admission_budget)
            if self.default_tenant is None:
                self.default_tenant = name
        _metrics.gauge("serving.tenants").set(len(self.tenants))
        info = engine.warmup() if warm else {}
        return {"tenant": name, "warmup": info}

    def add_tenant_from_dir(self, name: str, model_dir: str,
                            config: Optional[ServingConfig] = None,
                            admission_budget: Optional[int] = None,
                            mesh=None, warm: bool = True) -> dict:
        from photon_tpu.io.model_io import load_for_serving

        cfg = config or self.config
        serving_model = load_for_serving(model_dir)
        model = DeviceResidentModel(
            serving_model, mesh=mesh, feature_pad=cfg.feature_pad,
            coeff_store=cfg.coeff_store, append_reserve=cfg.append_reserve,
            int8=cfg.int8_serving)
        return self.add_tenant(name, model, config=cfg,
                               admission_budget=admission_budget, warm=warm)

    def remove_tenant(self, name: str, drain_budget_s: float = 0.0) -> None:
        with self._lock:
            st = self.tenants.pop(name, None)
            if st is None:
                raise KeyError(f"tenant {name!r} not hosted")
            if self.default_tenant == name:
                self.default_tenant = next(iter(self.tenants), None)
        if st.canary_engine is not None:
            st.canary_engine.model.close_stores()
        st.engine.shutdown(drain_budget_s=drain_budget_s,
                           reason=f"tenant {name} removed")
        _metrics.gauge("serving.tenants").set(len(self.tenants))

    def _get(self, name: str) -> TenantState:
        st = self.tenants.get(name)
        if st is None:
            raise KeyError(f"tenant {name!r} not hosted")
        return st

    # -- warmup & program bundles -------------------------------------------

    def warmup(self) -> dict:
        """Warm every tenant's ladder. Same-shape tenants after the first
        are pure jitcache hits — the aggregate compile_counts show one
        shape's worth of builds, not N."""
        infos = {}
        for name, st in list(self.tenants.items()):
            infos[name] = st.engine.warmup()
        return {"tenants": infos,
                "programs": sum(i.get("programs", 0) for i in infos.values()),
                "compile_counts": compile_cache.compile_counts()}

    def load_program_bundles(self, base_dir: str) -> dict:
        """Seed the jitcache from AOT bundles under ``base_dir`` (one
        subdirectory per distinct shape signature) so the subsequent
        ``warmup`` performs zero traces. Refusals fall back silently —
        the tenant just warms by tracing."""
        from photon_tpu.serving import programs as _programs

        out = {}
        done = {}
        buckets = _ladder_buckets(self.config)
        for name, st in self.tenants.items():
            d = _programs.bundle_dir_for(base_dir, st.engine.model)
            if d in done:  # same shape signature: already seeded
                out[name] = {**done[d], "shared_with": done[d]["tenant"]}
                continue
            got = _programs.load_program_bundle(st.engine.model, buckets, d)
            done[d] = {**got, "tenant": name}
            out[name] = got
        return out

    def export_program_bundles(self, base_dir: str) -> dict:
        """Export each distinct shape signature's warmed ladder (one
        bundle subdirectory per signature — same-shape tenants share)."""
        from photon_tpu.serving import programs as _programs

        out = {}
        done = set()
        buckets = _ladder_buckets(self.config)
        for name, st in self.tenants.items():
            d = _programs.bundle_dir_for(base_dir, st.engine.model)
            if d in done:
                continue
            done.add(d)
            out[name] = _programs.export_program_bundle(
                st.engine.model, buckets, d)
        return out

    # -- routing -------------------------------------------------------------

    def _refuse(self, request: ScoreRequest, tenant: str,
                reason: FallbackReason, detail: str) -> ScoreResponse:
        _metrics.counter("serving.tenant_refused", tenant=tenant,
                         reason=reason.value).inc()
        return ScoreResponse(
            request.uid, score=None, degraded=True,
            fallbacks=(Fallback(reason, detail=detail),),
            tenant=tenant if tenant != "?" else None)

    @staticmethod
    def canary_pick(tenant: str, uid: str, fraction: float) -> bool:
        """Deterministic traffic split: stable per (tenant, uid), no RNG,
        identical across processes and restarts — crc32 of "tenant:uid"
        against a 10000-slot wheel."""
        if fraction <= 0.0:
            return False
        return (zlib.crc32(f"{tenant}:{uid}".encode()) % 10000
                < int(round(fraction * 10000)))

    def submit(self, request: ScoreRequest) -> Optional[ScoreResponse]:
        """Route one request to its tenant's live or canary arm. Returns
        an immediate typed refusal (unknown tenant, tenant budget, or
        the engine's own admission refusals) or None (queued; response
        arrives from ``pump``)."""
        name = request.tenant or self.default_tenant
        if name is None or name not in self.tenants:
            return self._refuse(
                request, name or "?", FallbackReason.UNKNOWN_TENANT,
                f"tenant {name!r} not hosted")
        st = self.tenants[name]
        _metrics.counter("serving.tenant_requests", tenant=name).inc()

        # noisy-neighbor chaos: this tenant's submit fans out into flood
        # duplicates that go through the SAME budget gate — the flood
        # lands on this tenant's queue or gets refused here, never on a
        # neighbor's queue
        for k in range(_chaos.tenant_flood_burst(name)):
            flood = ScoreRequest(
                f"{_FLOOD_PREFIX}{k}-{request.uid}", request.features,
                request.entity_ids, request.offset, request.timeout_s,
                tenant=name)
            _metrics.counter("serving.tenant_flood_injected",
                             tenant=name).inc()
            self._submit_to(st, flood)  # refusals/responses are dropped

        return self._submit_to(st, request)

    def _submit_to(self, st: TenantState,
                   request: ScoreRequest) -> Optional[ScoreResponse]:
        flood = request.uid.startswith(_FLOOD_PREFIX)
        if st.admission_budget is not None \
                and st.depth() >= st.admission_budget:
            resp = self._refuse(request, st.name,
                                FallbackReason.TENANT_BUDGET_EXCEEDED,
                                f"queued depth >= budget "
                                f"{st.admission_budget}")
            return None if flood else resp
        arm = "live"
        engine = st.engine
        if st.canary_engine is not None and not flood and \
                self.canary_pick(st.name, request.uid, st.canary_fraction):
            arm = "canary"
            engine = st.canary_engine
        if not flood:
            st.split_counts[arm] += 1
        rejected = engine.submit(request)
        if rejected is not None:
            if flood:
                _metrics.counter("serving.tenant_flood_dropped",
                                 tenant=st.name).inc()
                return None
            rejected.tenant = st.name
            rejected.arm = arm
            return rejected
        return None

    def pump(self, flush: bool = False) -> List[ScoreResponse]:
        """Pump every tenant's arms once; responses come back tagged with
        typed (tenant, arm) attribution. Chaos flood responses are
        dropped here (counted), so callers only ever see real traffic."""
        out: List[ScoreResponse] = []
        for name, st in list(self.tenants.items()):
            arms = [("live", st.engine)]
            if st.canary_engine is not None:
                arms.append(("canary", st.canary_engine))
            for arm, engine in arms:
                for resp in engine.pump(flush=flush):
                    if resp.uid.startswith(_FLOOD_PREFIX):
                        _metrics.counter("serving.tenant_flood_dropped",
                                         tenant=name).inc()
                        continue
                    resp.tenant = name
                    resp.arm = arm
                    _metrics.counter("serving.tenant_responses",
                                     tenant=name, arm=arm).inc()
                    out.append(resp)
        return out

    def serve(self, requests: Sequence[ScoreRequest]) -> List[ScoreResponse]:
        """Synchronous convenience mirroring ``ServingEngine.serve``:
        responses in request order, every degradation typed."""
        by_uid: Dict[str, List[ScoreResponse]] = {}
        for r in requests:
            rejected = self.submit(r)
            if rejected is not None:
                by_uid.setdefault(r.uid, []).append(rejected)
            for resp in self.pump(flush=any(
                    st.depth() >= self.config.max_batch
                    for st in self.tenants.values())):
                by_uid.setdefault(resp.uid, []).append(resp)
        while any(st.depth() for st in self.tenants.values()):
            got = self.pump(flush=True)
            if not got:
                break
            for resp in got:
                by_uid.setdefault(resp.uid, []).append(resp)
        return [by_uid[r.uid].pop(0) for r in requests]

    # -- canary / A-B --------------------------------------------------------

    def start_canary(self, tenant: str, serving_model, label: str,
                     fraction: float, mesh=None):
        """Gate-validate a candidate for ``tenant`` (the FULL swap
        ladder, publish withheld) and, on pass, open a canary arm that
        receives ``fraction`` of the tenant's traffic. Returns the
        SwapResult; ``accepted=False`` means no canary was opened and
        the reason names the failing gate."""
        from photon_tpu.serving.swap import swap_staged

        if not 0.0 < fraction <= 1.0:
            raise ValueError("canary fraction must be in (0, 1]")
        st = self._get(tenant)
        if st.canary_engine is not None:
            raise RuntimeError(f"tenant {tenant!r} already has a canary "
                               f"({st.canary_label!r}); promote or abort it")
        result = swap_staged(st.engine, serving_model, label, mesh=mesh,
                             publish=False)
        if not result.accepted:
            return result
        canary = ServingEngine(result.staged_model, config=st.engine.config,
                               clock=self._clock,
                               obs_labels={"tenant": tenant, "arm": "canary"})
        canary.warmup()  # programs already compiled: pure jitcache hits
        st.canary_engine = canary
        st.canary_label = label
        st.canary_fraction = float(fraction)
        st.split_counts = {"live": 0, "canary": 0}
        _metrics.counter("serving.canary_started", tenant=tenant).inc()
        return result

    def promote_canary(self, tenant: str) -> dict:
        """Publish the canary model as the tenant's live model (normal
        swap semantics: prior retained for rollback, probation armed)
        and close the canary arm."""
        st = self._get(tenant)
        if st.canary_engine is None:
            raise RuntimeError(f"tenant {tenant!r} has no canary")
        # flush whatever the canary arm still has queued before its
        # engine wrapper is discarded (the model itself lives on)
        st.canary_engine.drain()
        published = st.engine.publish_model(st.canary_engine.model,
                                            st.canary_label or "canary")
        splits = dict(st.split_counts)
        st.canary_engine = None
        st.canary_label = None
        st.canary_fraction = 0.0
        _metrics.counter("serving.canary_promoted", tenant=tenant).inc()
        return {**published, "splits": splits}

    def abort_canary(self, tenant: str) -> dict:
        """Drop the canary arm; its model's stores are closed. The live
        model never changed, so there is nothing to roll back."""
        st = self._get(tenant)
        if st.canary_engine is None:
            raise RuntimeError(f"tenant {tenant!r} has no canary")
        st.canary_engine.drain()
        st.canary_engine.model.close_stores()
        splits = dict(st.split_counts)
        label = st.canary_label
        st.canary_engine = None
        st.canary_label = None
        st.canary_fraction = 0.0
        _metrics.counter("serving.canary_aborted", tenant=tenant).inc()
        return {"label": label, "splits": splits}

    # -- lifecycle / stats ---------------------------------------------------

    def begin_drain(self, reason: str = "drain requested") -> None:
        for st in self.tenants.values():
            st.engine.begin_drain(reason)
            if st.canary_engine is not None:
                st.canary_engine.begin_drain(reason)

    @property
    def draining(self) -> bool:
        return any(st.engine.draining for st in self.tenants.values())

    def drain(self) -> List[ScoreResponse]:
        """Flush every tenant's queued requests to completion (stream
        end) — tagged like ``pump`` output."""
        out: List[ScoreResponse] = []
        while any(st.depth() for st in self.tenants.values()):
            got = self.pump(flush=True)
            if not got:
                break
            out.extend(got)
        return out

    def shutdown(self, drain_budget_s: Optional[float] = None,
                 reason: str = "shutdown") -> List[ScoreResponse]:
        """Drain every tenant within the budget; mirrors
        ``ServingEngine.shutdown`` (flat tagged response list) so the CLI
        driver treats both engine kinds identically."""
        out: List[ScoreResponse] = []
        for name, st in list(self.tenants.items()):
            if st.canary_engine is not None:
                for resp in st.canary_engine.shutdown(drain_budget_s=0.0,
                                                      reason=reason):
                    if resp.uid.startswith(_FLOOD_PREFIX):
                        continue
                    resp.tenant = name
                    resp.arm = "canary"
                    out.append(resp)
                st.canary_engine.model.close_stores()
                st.canary_engine = None
            for resp in st.engine.shutdown(drain_budget_s=drain_budget_s,
                                           reason=reason):
                if resp.uid.startswith(_FLOOD_PREFIX):
                    continue
                resp.tenant = name
                resp.arm = "live"
                out.append(resp)
        return out

    def stats(self) -> dict:
        out = {"tenants": {}, "default_tenant": self.default_tenant}
        for name, st in self.tenants.items():
            entry = {"live": st.engine.stats(),
                     "admission_budget": st.admission_budget,
                     "splits": dict(st.split_counts)}
            if st.canary_engine is not None:
                entry["canary"] = {"label": st.canary_label,
                                   "fraction": st.canary_fraction,
                                   "stats": st.canary_engine.stats()}
            out["tenants"][name] = entry
        return out


def _ladder_buckets(config: ServingConfig):
    from photon_tpu.serving.batching import BucketLadder

    return BucketLadder(config.max_batch, config.min_bucket).buckets
