"""Bootstrap training: coefficient / metric confidence intervals.

Reference: photon-diagnostics BootstrapTraining.scala:29 (train k models
on bootstrap samples, aggregate via CoefficientSummary) and
supervised/model/CoefficientSummary.scala (mean/min/max/stddev/quartiles).

TPU re-design: a bootstrap sample is a per-sample multiplicity drawn from
Multinomial(n, 1/n) — equivalently a weight vector multiplying the
original weights — so the k resampled trainings become ONE vmapped solve
over a [k, n] weight matrix. No data movement, no reshuffles.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.data.dataset import DataBatch
from photon_tpu.function.objective import GLMObjective, Hyper
from photon_tpu.ops.losses import loss_for_task
from photon_tpu.optim import lbfgs, owlqn, tron
from photon_tpu.types import OptimizerType, TaskType

Array = jax.Array


@dataclasses.dataclass
class CoefficientSummary:
    """Summary stats of one coefficient across bootstrap replicas
    (reference: CoefficientSummary.scala)."""

    values: np.ndarray

    @property
    def mean(self) -> float:
        return float(np.mean(self.values))

    @property
    def std_dev(self) -> float:
        return float(np.std(self.values, ddof=1)) if len(self.values) > 1 else 0.0

    @property
    def min(self) -> float:
        return float(np.min(self.values))

    @property
    def max(self) -> float:
        return float(np.max(self.values))

    def quantile(self, q: float) -> float:
        s = np.sort(self.values)
        return float(s[min(int(q * len(s)), len(s) - 1)])

    @property
    def first_quartile(self) -> float:
        return self.quantile(0.25)

    @property
    def median(self) -> float:
        return self.quantile(0.5)

    @property
    def third_quartile(self) -> float:
        return self.quantile(0.75)

    @property
    def count(self) -> int:
        return len(self.values)

    def __str__(self) -> str:
        return (f"Range: [Min: {self.min:.3f}, Q1: {self.first_quartile:.3f}, "
                f"Med: {self.median:.3f}, Q3: {self.third_quartile:.3f}, "
                f"Max: {self.max:.3f}) Mean: [{self.mean:.3f}], "
                f"Std. Dev.[{self.std_dev:.3f}], # samples = [{self.count}]")


def bootstrap_weights(key: Array, num_samples: int, n: int,
                      portion: float = 1.0) -> Array:
    """[k, n] resampling multiplicities ~ Multinomial(round(portion*n), 1/n)
    per replica — the weight-space equivalent of sampling rows with
    replacement."""
    draws = max(int(round(portion * n)), 1)
    keys = jax.random.split(key, num_samples)

    def one(k):
        idx = jax.random.randint(k, (draws,), 0, n)
        return jnp.zeros((n,), jnp.float32).at[idx].add(1.0)

    return jax.vmap(one)(keys)


def bootstrap_training(
    task: TaskType,
    batch: DataBatch,
    dim: int,
    num_bootstrap_samples: int,
    portion: float = 1.0,
    l2_weight: float = 0.0,
    l1_weight: float = 0.0,
    optimizer_type: OptimizerType = OptimizerType.LBFGS,
    solver_config=None,
    seed: int = 0,
    evaluate_fn: Optional[Callable[[Array], Dict[str, float]]] = None,
) -> Dict[str, object]:
    """Train ``num_bootstrap_samples`` models on resampled data in one
    vmapped solve; returns {"models": [k, d], "coefficients":
    [CoefficientSummary]*d, "metrics": {name: CoefficientSummary}}."""
    assert num_bootstrap_samples > 1, "need more than one bootstrap sample"
    assert 0 < portion <= 1.0, "portion must be in (0, 1]"
    from photon_tpu.optim.base import SolverConfig

    cfg = solver_config or SolverConfig(max_iterations=100, tolerance=1e-7)
    n = batch.num_samples
    base_w = batch.weights if batch.weights is not None \
        else jnp.ones_like(batch.labels)
    mults = bootstrap_weights(jax.random.PRNGKey(seed),
                              num_bootstrap_samples, n, portion)
    obj = GLMObjective(loss_for_task(task))
    dtype = batch.labels.dtype
    l2 = jnp.asarray(l2_weight, dtype)
    l1 = jnp.asarray(l1_weight, dtype)

    def solve_one(mult):
        b = DataBatch(batch.features, batch.labels, batch.offsets,
                      base_w * mult.astype(dtype))
        hyper = Hyper(l2_weight=l2)
        vg = lambda c: obj.value_and_gradient(c, b, hyper)
        x0 = jnp.zeros((dim,), dtype)
        if optimizer_type == OptimizerType.OWLQN:
            return owlqn.minimize(vg, x0, l1_weight=l1, config=cfg).coef
        if optimizer_type == OptimizerType.TRON:
            hv = lambda c, v: obj.hessian_vector(c, v, b, hyper)
            return tron.minimize(vg, hv, x0, config=cfg).coef
        if optimizer_type == OptimizerType.NEWTON:
            # batched-Cholesky IRLS — a natural fit for this vmapped solve
            from photon_tpu.optim import newton
            hm = lambda c: obj.hessian_matrix_from_weights(
                obj.hessian_weights(c, b), dim, b, hyper)
            return newton.minimize(vg, hm, x0, config=cfg).coef
        return lbfgs.minimize(vg, x0, config=cfg).coef

    models = jax.jit(jax.vmap(solve_one))(mults)
    models_np = np.asarray(models)

    out: Dict[str, object] = {
        "models": models_np,
        "coefficients": aggregate_coefficient_confidence_intervals(models_np),
    }
    if evaluate_fn is not None:
        per_model = [evaluate_fn(models[i]) for i in range(num_bootstrap_samples)]
        out["metrics"] = aggregate_metrics_confidence_intervals(per_model)
    return out


def aggregate_coefficient_confidence_intervals(
        models: np.ndarray) -> List[CoefficientSummary]:
    """[k, d] coefficient matrix -> one summary per coefficient."""
    return [CoefficientSummary(models[:, j]) for j in range(models.shape[1])]


def aggregate_metrics_confidence_intervals(
        metrics: Sequence[Dict[str, float]]) -> Dict[str, CoefficientSummary]:
    names = metrics[0].keys()
    return {name: CoefficientSummary(np.asarray([m[name] for m in metrics]))
            for name in names}
