"""Fitting (learning-curve) diagnostic.

Reference: photon-diagnostics diagnostics/fitting/FittingDiagnostic
.scala:33 — train on growing fractions of the data, record the train and
holdout metric per fraction; diverging curves diagnose over/under-fit.

TPU re-design: a "fraction" is a prefix mask over a fixed permutation, so
every sub-training reuses the same compiled solve with a masked weight
vector — no data subsetting, no recompiles.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.data.dataset import DataBatch

Array = jax.Array

DEFAULT_FRACTIONS = (0.1, 0.25, 0.5, 0.75, 1.0)


@dataclasses.dataclass
class FittingReport:
    fractions: List[float]
    train_metrics: Dict[str, List[float]]
    test_metrics: Dict[str, List[float]]

    def summary(self) -> str:
        parts = []
        for name in self.train_metrics:
            parts.append(
                f"{name}: train {self.train_metrics[name][-1]:.4f} / "
                f"test {self.test_metrics[name][-1]:.4f} at full data")
        return "; ".join(parts)


def fitting_diagnostic(
    batch: DataBatch,
    train_model: Callable[[DataBatch], object],
    evaluate: Callable[[object, str], Dict[str, float]],
    fractions: Sequence[float] = DEFAULT_FRACTIONS,
    seed: int = 0,
) -> FittingReport:
    """``train_model(masked_batch) -> model``;
    ``evaluate(model, split) -> {metric: value}`` with split in
    {"train", "test"}."""
    n = batch.num_samples
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n)
    base_w = (np.asarray(batch.weights) if batch.weights is not None
              else np.ones(n))

    train_out: Dict[str, List[float]] = {}
    test_out: Dict[str, List[float]] = {}
    used: List[float] = []
    for frac in fractions:
        k = max(int(frac * n), 1)
        mask = np.zeros(n)
        mask[perm[:k]] = 1.0
        masked = DataBatch(batch.features, batch.labels, batch.offsets,
                           jnp.asarray(base_w * mask, batch.labels.dtype))
        model = train_model(masked)
        used.append(frac)
        for split, out in (("train", train_out), ("test", test_out)):
            for name, v in evaluate(model, split).items():
                out.setdefault(name, []).append(float(v))
    return FittingReport(fractions=used, train_metrics=train_out,
                         test_metrics=test_out)
