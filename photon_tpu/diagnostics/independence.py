"""Kendall-tau independence analysis (e.g. prediction-error independence).

Reference: photon-diagnostics diagnostics/independence/KendallTauAnalysis
.scala — concordant/discordant pair counts over (a, b) pairs, tau-alpha
and tau-beta (tie-corrected), normal-approximation z score and p-value;
large inputs are subsampled to ~sqrt(n) pairs as in the reference.
"""

from __future__ import annotations

import dataclasses

import numpy as np
from scipy.stats import norm as _norm


@dataclasses.dataclass
class KendallTauReport:
    num_concordant: int
    num_discordant: int
    num_ties_a: int
    num_ties_b: int
    num_items: int
    tau_alpha: float
    tau_beta: float
    z_alpha: float
    p_value: float     # P[|Z| <= |z|]: mass INSIDE +-z (reference convention)
    message: str = ""

    def summary(self) -> str:
        return (f"tau_a = {self.tau_alpha:.4f}, tau_b = {self.tau_beta:.4f}, "
                f"z = {self.z_alpha:.3f} (P inside = {self.p_value:.4f})")


def _from_counts(nc: int, nd: int, ties_a: int, ties_b: int,
                 n: int) -> KendallTauReport:
    pairs = n * (n - 1) // 2
    no_ties_a = pairs - ties_a
    no_ties_b = pairs - ties_b
    denom = nc + nd
    tau_alpha = (nc - nd) / denom if denom else 0.0
    tb_denom = np.sqrt(float(no_ties_a) * float(no_ties_b))
    tau_beta = (nc - nd) / tb_denom if tb_denom > 0 else 0.0
    a = 2.0 * (2.0 * n + 5.0)
    b = 9.0 * n * (n - 1)
    d = np.sqrt(a / b) if b > 0 else 1.0
    z = tau_alpha / d
    p = float(_norm.cdf(abs(z)) - _norm.cdf(-abs(z)))
    msg = ""
    if ties_a + ties_b > 0:
        msg = (f"detected ties (ties in first variable: {ties_a}, ties in "
               f"second variable: {ties_b}); tau-beta corrects for ties")
    return KendallTauReport(nc, nd, ties_a, ties_b, n, tau_alpha,
                            float(tau_beta), float(z), p, msg)


def kendall_tau(a: np.ndarray, b: np.ndarray,
                max_items: int = 2000, seed: int = 0) -> KendallTauReport:
    """Exact O(n^2) pair counting after optional subsampling (the
    reference samples ~sqrt(count) of large RDDs)."""
    a = np.asarray(a, float)
    b = np.asarray(b, float)
    assert a.shape == b.shape
    n = len(a)
    if n > max_items:
        idx = np.random.default_rng(seed).choice(n, max_items, replace=False)
        a, b = a[idx], b[idx]
        n = max_items

    da = np.sign(a[:, None] - a[None, :])
    db = np.sign(b[:, None] - b[None, :])
    upper = np.triu(np.ones((n, n), bool), 1)
    prod = da * db
    nc = int(np.sum((prod > 0) & upper))
    nd = int(np.sum((prod < 0) & upper))
    ties_a = int(np.sum((da == 0) & upper))
    ties_b = int(np.sum((db == 0) & upper))
    return _from_counts(nc, nd, ties_a, ties_b, n)
