"""Model diagnostics: bootstrap CIs, calibration, learning curves,
feature importance, independence analysis, report rendering.

Replaces the reference's photon-diagnostics module.
"""

from photon_tpu.diagnostics.bootstrap import (
    CoefficientSummary,
    aggregate_coefficient_confidence_intervals,
    aggregate_metrics_confidence_intervals,
    bootstrap_training,
    bootstrap_weights,
)
from photon_tpu.diagnostics.fitting import FittingReport, fitting_diagnostic
from photon_tpu.diagnostics.hl import (
    HosmerLemeshowBin,
    HosmerLemeshowReport,
    hosmer_lemeshow,
)
from photon_tpu.diagnostics.importance import (
    FeatureImportanceReport,
    expected_magnitude_importance,
    variance_importance,
)
from photon_tpu.diagnostics.independence import KendallTauReport, kendall_tau
from photon_tpu.diagnostics.reporting import (
    BulletedList,
    Chapter,
    Document,
    NumberedList,
    Section,
    SimpleText,
    Table,
    render_html,
    render_text,
)

__all__ = [
    "CoefficientSummary", "bootstrap_training", "bootstrap_weights",
    "aggregate_coefficient_confidence_intervals",
    "aggregate_metrics_confidence_intervals",
    "FittingReport", "fitting_diagnostic",
    "HosmerLemeshowBin", "HosmerLemeshowReport", "hosmer_lemeshow",
    "FeatureImportanceReport", "expected_magnitude_importance",
    "variance_importance",
    "KendallTauReport", "kendall_tau",
    "Document", "Chapter", "Section", "SimpleText", "BulletedList",
    "NumberedList", "Table", "render_text", "render_html",
]
