"""Logical report tree + text/HTML renderers.

Reference: photon-diagnostics diagnostics/reporting/ — a LogicalReport
tree (Document -> Chapter -> Section -> items: SimpleText, bulleted /
numbered lists, tables) rendered by pluggable strategies with HTML
(reporting/html/*.scala) and text (reporting/text/*.scala) backends.
"""

from __future__ import annotations

import dataclasses
import html as _html
from typing import List, Sequence, Union


@dataclasses.dataclass
class SimpleText:
    text: str


@dataclasses.dataclass
class BulletedList:
    items: List[str]


@dataclasses.dataclass
class NumberedList:
    items: List[str]


@dataclasses.dataclass
class Table:
    header: List[str]
    rows: List[Sequence]
    caption: str = ""


ReportItem = Union[SimpleText, BulletedList, NumberedList, Table]


@dataclasses.dataclass
class Section:
    title: str
    items: List[ReportItem] = dataclasses.field(default_factory=list)

    def add(self, item: ReportItem) -> "Section":
        self.items.append(item)
        return self


@dataclasses.dataclass
class Chapter:
    title: str
    sections: List[Section] = dataclasses.field(default_factory=list)

    def add(self, section: Section) -> "Chapter":
        self.sections.append(section)
        return self


@dataclasses.dataclass
class Document:
    title: str
    chapters: List[Chapter] = dataclasses.field(default_factory=list)

    def add(self, chapter: Chapter) -> "Document":
        self.chapters.append(chapter)
        return self


# ---------------------------------------------------------------------------
# renderers
# ---------------------------------------------------------------------------


def _render_item_text(item: ReportItem, out: List[str]) -> None:
    if isinstance(item, SimpleText):
        out.append(item.text)
    elif isinstance(item, BulletedList):
        out.extend(f"  * {x}" for x in item.items)
    elif isinstance(item, NumberedList):
        out.extend(f"  {i + 1}. {x}" for i, x in enumerate(item.items))
    elif isinstance(item, Table):
        if item.caption:
            out.append(item.caption)
        widths = [max(len(str(h)), *(len(str(r[j])) for r in item.rows))
                  if item.rows else len(str(h))
                  for j, h in enumerate(item.header)]
        fmt = " | ".join(f"{{:<{w}}}" for w in widths)
        out.append(fmt.format(*item.header))
        out.append("-+-".join("-" * w for w in widths))
        out.extend(fmt.format(*(str(c) for c in r)) for r in item.rows)
    else:
        out.append(str(item))


def render_text(doc: Document) -> str:
    out: List[str] = [doc.title, "=" * len(doc.title), ""]
    for ch in doc.chapters:
        out += [ch.title, "-" * len(ch.title)]
        for sec in ch.sections:
            out += ["", f"## {sec.title}"]
            for item in sec.items:
                _render_item_text(item, out)
        out.append("")
    return "\n".join(out)


def _render_item_html(item: ReportItem, out: List[str]) -> None:
    esc = _html.escape
    if isinstance(item, SimpleText):
        out.append(f"<p>{esc(item.text)}</p>")
    elif isinstance(item, BulletedList):
        out.append("<ul>" + "".join(f"<li>{esc(x)}</li>" for x in item.items)
                   + "</ul>")
    elif isinstance(item, NumberedList):
        out.append("<ol>" + "".join(f"<li>{esc(x)}</li>" for x in item.items)
                   + "</ol>")
    elif isinstance(item, Table):
        rows = "".join(
            "<tr>" + "".join(f"<td>{esc(str(c))}</td>" for c in r) + "</tr>"
            for r in item.rows)
        head = "<tr>" + "".join(f"<th>{esc(h)}</th>" for h in item.header) + "</tr>"
        cap = f"<caption>{esc(item.caption)}</caption>" if item.caption else ""
        out.append(f"<table border='1'>{cap}{head}{rows}</table>")
    else:
        out.append(f"<p>{esc(str(item))}</p>")


def render_html(doc: Document) -> str:
    esc = _html.escape
    out = [f"<html><head><title>{esc(doc.title)}</title></head><body>",
           f"<h1>{esc(doc.title)}</h1>"]
    for ch in doc.chapters:
        out.append(f"<h2>{esc(ch.title)}</h2>")
        for sec in ch.sections:
            out.append(f"<h3>{esc(sec.title)}</h3>")
            for item in sec.items:
                _render_item_html(item, out)
    out.append("</body></html>")
    return "\n".join(out)
