"""Feature importance diagnostics.

Reference: photon-diagnostics diagnostics/featureimportance/
ExpectedMagnitudeFeatureImportanceDiagnostic.scala (importance =
|w_j| * E[|x_j|] when a feature summary exists, else |w_j|) and
VarianceFeatureImportanceDiagnostic (|w_j| * sd(x_j)); importances are
ranked descending and bucketed into rank fractions.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from photon_tpu.data.stats import FeatureDataStatistics

MAX_RANKED_FEATURES = 15


@dataclasses.dataclass
class FeatureImportanceReport:
    importance_type: str
    description: str
    # (feature key, column, importance), descending importance
    ranked: List[Tuple[str, int, float]]
    # rank fraction (0-1) -> importance value at that rank
    rank_to_importance: Dict[float, float]

    def top(self, k: int = MAX_RANKED_FEATURES):
        return self.ranked[:k]


def _report(kind: str, description: str, importances: np.ndarray,
            names: Optional[List[str]]) -> FeatureImportanceReport:
    order = np.argsort(-importances, kind="stable")
    ranked = [(names[j] if names else str(j), int(j), float(importances[j]))
              for j in order]
    fractions = np.linspace(0.0, 1.0, 11)
    rank_to_imp = {
        float(f): float(importances[order[min(int(f * (len(order) - 1)),
                                              len(order) - 1)]])
        for f in fractions} if len(order) else {}
    return FeatureImportanceReport(kind, description, ranked, rank_to_imp)


def expected_magnitude_importance(
    coefficients: np.ndarray,
    summary: Optional[FeatureDataStatistics] = None,
    feature_names: Optional[List[str]] = None,
) -> FeatureImportanceReport:
    """|w_j| * E[|x_j|] (mean magnitude approximated by |mean| + sd, as the
    reference uses the summary's expected absolute value when present)."""
    w = np.abs(np.asarray(coefficients, float))
    if summary is not None:
        exp_abs = np.abs(np.asarray(summary.mean)) + np.sqrt(
            np.maximum(np.asarray(summary.variance), 0))
        imp = w * exp_abs
        desc = "Expected magnitude of inner product contribution"
    else:
        imp = w
        desc = "Magnitude of feature coefficient"
    return _report("Inner product expectation", desc, imp, feature_names)


def variance_importance(
    coefficients: np.ndarray,
    summary: Optional[FeatureDataStatistics] = None,
    feature_names: Optional[List[str]] = None,
) -> FeatureImportanceReport:
    """|w_j| * sd(x_j): contribution to score variance."""
    w = np.abs(np.asarray(coefficients, float))
    if summary is not None:
        imp = w * np.sqrt(np.maximum(np.asarray(summary.variance), 0))
        desc = "Contribution to score standard deviation"
    else:
        imp = w
        desc = "Magnitude of feature coefficient"
    return _report("Variance", desc, imp, feature_names)
