"""Hosmer-Lemeshow calibration diagnostic for logistic models.

Reference: photon-diagnostics diagnostics/hl/HosmerLemeshowDiagnostic
.scala:29 — bin samples by predicted probability, chi^2 over
(observed - expected) positive AND negative counts per bin, degrees of
freedom = bins - 2, p-value + standard confidence cutoffs; bins with
expected counts below a minimum are flagged.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np
from scipy.stats import chi2 as _chi2

MINIMUM_EXPECTED_IN_BUCKET = 5.0
CONFIDENCE_CUTOFFS = (0.90, 0.95, 0.99, 0.99999999)


@dataclasses.dataclass
class HosmerLemeshowBin:
    """One predicted-probability bin (reference:
    PredictedProbabilityVersusObservedFrequencyHistogramBin)."""

    lower: float
    upper: float
    count: int
    observed_pos: float
    expected_pos: float

    @property
    def observed_neg(self) -> float:
        return self.count - self.observed_pos

    @property
    def expected_neg(self) -> float:
        return self.count - self.expected_pos

    @property
    def chi_square_term(self) -> float:
        d = 0.0
        if self.expected_pos > 0:
            d += (self.observed_pos - self.expected_pos) ** 2 / self.expected_pos
        if self.expected_neg > 0:
            d += (self.observed_neg - self.expected_neg) ** 2 / self.expected_neg
        return d

    @property
    def too_small(self) -> bool:
        return (self.expected_pos < MINIMUM_EXPECTED_IN_BUCKET
                or self.expected_neg < MINIMUM_EXPECTED_IN_BUCKET)


@dataclasses.dataclass
class HosmerLemeshowReport:
    bins: List[HosmerLemeshowBin]
    chi_square: float
    degrees_of_freedom: int
    p_value: float                      # P[chi2 >= observed] under H0
    cutoffs: dict                       # confidence -> chi2 threshold
    warnings: List[str]

    @property
    def well_calibrated(self, confidence: float = 0.95) -> bool:
        return self.chi_square <= self.cutoffs[0.95]

    def summary(self) -> str:
        return (f"HL chi2 = {self.chi_square:.3f} on {self.degrees_of_freedom} "
                f"d.o.f. (P[>=] = {self.p_value:.4g}); "
                f"{len(self.warnings)} bin warning(s)")


def hosmer_lemeshow(
    labels: np.ndarray,
    predicted_probabilities: np.ndarray,
    num_bins: int = 10,
    weights: Optional[np.ndarray] = None,
) -> HosmerLemeshowReport:
    """Equal-frequency (decile) binning by predicted probability."""
    labels = np.asarray(labels, float)
    p = np.asarray(predicted_probabilities, float)
    w = np.ones_like(p) if weights is None else np.asarray(weights, float)

    order = np.argsort(p, kind="stable")
    p_s, y_s, w_s = p[order], labels[order], w[order]
    edges = np.linspace(0, len(p), num_bins + 1).astype(int)

    bins: List[HosmerLemeshowBin] = []
    warnings: List[str] = []
    for b in range(num_bins):
        lo, hi = edges[b], edges[b + 1]
        if hi <= lo:
            continue
        wb = w_s[lo:hi]
        bins.append(HosmerLemeshowBin(
            lower=float(p_s[lo]), upper=float(p_s[hi - 1]),
            count=float(np.sum(wb)),
            observed_pos=float(np.sum(wb * (y_s[lo:hi] > 0.5))),
            expected_pos=float(np.sum(wb * p_s[lo:hi])),
        ))
        if bins[-1].too_small:
            warnings.append(
                f"bin [{bins[-1].lower:.3f}, {bins[-1].upper:.3f}]: expected "
                f"count too small for a sound chi^2 estimate")

    chi_sq = float(sum(b.chi_square_term for b in bins))
    dof = max(len(bins) - 2, 1)
    dist = _chi2(dof)
    return HosmerLemeshowReport(
        bins=bins,
        chi_square=chi_sq,
        degrees_of_freedom=dof,
        p_value=float(dist.sf(chi_sq)),
        cutoffs={c: float(dist.ppf(c)) for c in CONFIDENCE_CUTOFFS},
        warnings=warnings,
    )
